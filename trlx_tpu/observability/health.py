"""Training-health monitor: streaming RLHF drift detection.

PR 8 watches whether the run is *fast* (spans, MFU, incident bundles); this
module watches whether it is *healthy*. RLHF has a family of silent failure
modes — reward hacking shifts the score distribution, a saturated KL
controller stops constraining the policy, entropy collapse precedes mode
collapse, a value head that explains no variance starves PPO of advantage
signal, and degenerate generations (truncation walls, n-gram loops) poison
the store — none of which crash anything. They are only visible as trends,
and with the asynchronous staleness-tolerant pipelines the ROADMAP pushes
toward, off-policy drift makes them MORE likely and HARDER to spot post-hoc.

The ``HealthMonitor`` holds one streaming detector per failure mode, fed
from data the trainer already materializes on the host (the log-boundary
stats dict, the rollout chunks crossing the reward boundary). Each detector
maps an observation to a severity (0/1/2) and runs it through a shared
hysteresis state machine: WARN only after ``warn_streak`` consecutive bad
observations, CRIT only after ``crit_streak`` consecutive severity-2
observations, and de-escalation ONE level at a time after ``warn_streak``
clean observations — a single noisy window never flips state, and a run
does not flap between CRIT and OK.

Outputs, all off the hot path:

- ``health/*`` gauges (per-detector state + the quantity it judges) merged
  into the Tracker's log-boundary record, plus a monotonic
  ``health/state_changes_total`` counter;
- per-chunk ``LineageRecord``s (weight version, staleness, truncation /
  degenerate rates) appended to ``<ckpt_dir>/lineage.jsonl`` — the audit
  trail that answers "which weights produced the rows that poisoned the
  store?";
- CRIT transitions escalate into PR 8's incident machinery through the
  ``register_emergency`` hook (``trlx_tpu/observability/anomaly.py``), so a
  detector trip leaves thread stacks + a metrics tail behind;
- the live ``/metrics`` + ``/healthz`` endpoints
  (``trlx_tpu/observability/export.py``) serve the same gauges to a
  Prometheus scraper while the run is alive.

Armed by ``train.health_monitor`` (or ``TRLX_TPU_HEALTH=1``), off by
default. Drillable on CPU: ``TRLX_TPU_FAULTS=reward_drift@N`` /
``entropy_collapse@N`` latch a perturbation of the OBSERVED stats (training
is untouched), so every WARN→CRIT path is exercisable without a real
divergence (tests/test_health.py).
"""

import json
import os
import threading
import time
from collections import deque
from dataclasses import asdict, dataclass

import numpy as np

from trlx_tpu.utils import jsonl

__all__ = [
    "OK",
    "WARN",
    "CRIT",
    "HysteresisDetector",
    "RewardDriftDetector",
    "KLHealthDetector",
    "EntropyCollapseDetector",
    "ExplainedVarianceDetector",
    "RolloutSentinel",
    "MixedVersionDetector",
    "LineageRecord",
    "HealthMonitor",
    "truncation_rate",
    "degenerate_rate",
]

OK, WARN, CRIT = "ok", "warn", "crit"
_LEVEL = {OK: 0, WARN: 1, CRIT: 2}
_STATE = {0: OK, 1: WARN, 2: CRIT}


class HysteresisDetector:
    """Severity stream -> OK/WARN/CRIT state machine with hysteresis.

    Subclasses implement ``severity(obs) -> 0|1|2`` (pure judgment, no state
    transitions). ``observe(obs)`` runs the shared transition rules:

    - OK -> WARN after ``warn_streak`` consecutive observations with
      severity >= 1;
    - -> CRIT after ``crit_streak`` consecutive severity-2 observations
      (a CRIT always passes through WARN on the way up, so consumers see
      the full OK -> WARN -> CRIT trajectory);
    - de-escalation is ONE level per ``clear_streak`` consecutive clean
      observations (CRIT -> WARN -> OK needs two full clean streaks), so a
      brief recovery inside an incident never silently clears it.

    Every transition increments ``state_changes`` (the monotonic counter the
    Tracker/exporter surface); a transition INTO crit invokes ``on_crit``
    (the monitor routes it to the incident machinery) behind a guard — the
    escalation path must never take the training loop down."""

    name = "detector"

    def __init__(self, warn_streak: int = 2, crit_streak: int = 4, clear_streak=None):
        self.warn_streak = max(1, int(warn_streak))
        self.crit_streak = max(1, int(crit_streak))
        self.clear_streak = max(
            1, int(clear_streak if clear_streak is not None else warn_streak)
        )
        self.state = OK
        self.state_changes = 0
        self.last_severity = 0
        self.observations = 0
        self.on_crit = None  # set by HealthMonitor
        self._bad = 0  # consecutive severity >= 1
        self._crit = 0  # consecutive severity == 2
        self._clean = 0  # consecutive severity == 0

    def severity(self, obs) -> int:
        raise NotImplementedError

    def observe(self, obs) -> str:
        sev = int(self.severity(obs))
        self.last_severity = sev
        self.observations += 1
        if sev >= 1:
            self._clean = 0
            self._bad += 1
            self._crit = self._crit + 1 if sev == 2 else 0
        else:
            self._bad = self._crit = 0
            self._clean += 1
        level = _LEVEL[self.state]
        new = level
        if self._crit >= self.crit_streak:
            new = 2
        elif self._bad >= self.warn_streak:
            # Escalate to WARN; never knocks an established CRIT back down —
            # only a clean streak de-escalates.
            new = max(level, 1)
        elif self._clean >= self.clear_streak and level > 0:
            new = level - 1
            self._clean = 0  # the next level down costs another full streak
        if new != level:
            self.state = _STATE[new]
            self.state_changes += 1
            if new == 2 and self.on_crit is not None:
                try:
                    self.on_crit(self, obs)
                except Exception:  # noqa: BLE001 — escalation is best-effort
                    pass
        return self.state


class RewardDriftDetector(HysteresisDetector):
    """Reward-distribution drift: rolling mean of recent chunk scores vs a
    frozen warmup baseline, judged as a z-score. The sigma floor
    (``max(sigma0, 0.1|mu0|)``) keeps a freakishly-quiet warmup from turning
    ordinary fluctuation into WARNs."""

    name = "reward_drift"

    def __init__(self, warmup: int = 5, warn_z: float = 3.0, crit_z: float = 6.0,
                 recent_window: int = 4, **kw):
        super().__init__(**kw)
        self.warmup = max(1, int(warmup))
        self.warn_z = float(warn_z)
        self.crit_z = float(crit_z)
        self._baseline = []
        self._recent = deque(maxlen=max(1, int(recent_window)))
        self.mu0 = self.sigma0 = None
        self.z = 0.0

    def severity(self, x) -> int:
        x = float(x)
        if len(self._baseline) < self.warmup:
            self._baseline.append(x)
            return 0
        if self.mu0 is None:
            base = np.asarray(self._baseline, dtype=np.float64)
            self.mu0 = float(base.mean())
            self.sigma0 = max(float(base.std()), 0.1 * abs(self.mu0), 1e-3)
        self._recent.append(x)
        self.z = abs(float(np.mean(self._recent)) - self.mu0) / self.sigma0
        if self.z >= self.crit_z:
            return 2
        if self.z >= self.warn_z:
            return 1
        return 0


class KLHealthDetector(HysteresisDetector):
    """KL-controller health, judged only when an adaptive target exists:

    - sustained ``mean_kl`` ABOVE target (ratio >= warn_ratio WARNs,
      >= crit_ratio CRITs) — the policy is escaping the trust region faster
      than the controller reins it in;
    - sustained ``mean_kl`` far BELOW target WARNs only — an over-tight
      leash wastes the KL budget but is not dangerous;
    - coefficient saturation (kl_coef pinned ``sat_factor``x away from its
      init) WARNs — the controller has run out of authority, commonly a
      staleness symptom on the pipelined schedules (RUNBOOK.md §9)."""

    name = "kl"

    def __init__(self, warmup: int = 5, warn_ratio: float = 2.0, crit_ratio: float = 4.0,
                 sat_factor: float = 10.0, **kw):
        super().__init__(**kw)
        self.warmup = max(0, int(warmup))
        self.warn_ratio = float(warn_ratio)
        self.crit_ratio = float(crit_ratio)
        self.sat_factor = float(sat_factor)
        self.ratio = 0.0
        self.coef = 0.0
        self._seen = 0

    def severity(self, obs) -> int:
        kl, target = obs.get("kl"), obs.get("target")
        coef, init = obs.get("coef"), obs.get("init_coef")
        if coef is not None:
            self.coef = float(coef)
        if kl is None or target is None or float(target) <= 0:
            return 0  # fixed controller / no KL stats: nothing to judge
        self._seen += 1
        self.ratio = float(kl) / float(target)
        if self._seen <= self.warmup:
            return 0  # early KL is legitimately far from target
        sev = 0
        if self.ratio >= self.crit_ratio:
            sev = 2
        elif self.ratio >= self.warn_ratio or self.ratio <= 1.0 / self.warn_ratio:
            sev = 1
        if (
            coef is not None
            and init
            and (float(coef) >= self.sat_factor * float(init)
                 or float(coef) <= float(init) / self.sat_factor)
        ):
            sev = max(sev, 1)
        return sev


class EntropyCollapseDetector(HysteresisDetector):
    """Sampled-token entropy vs a warmup baseline: a policy whose entropy
    drops to a small fraction of where it started is converging on a narrow
    mode (often right before degenerate output)."""

    name = "entropy"

    def __init__(self, warmup: int = 5, warn_frac: float = 0.5, crit_frac: float = 0.2, **kw):
        super().__init__(**kw)
        self.warmup = max(1, int(warmup))
        self.warn_frac = float(warn_frac)
        self.crit_frac = float(crit_frac)
        self._baseline = []
        self.base = None
        self.value = 0.0

    def severity(self, e) -> int:
        self.value = float(e)
        if len(self._baseline) < self.warmup:
            self._baseline.append(self.value)
            return 0
        if self.base is None:
            self.base = float(np.mean(self._baseline))
        if self.base <= 1e-9:
            return 0  # degenerate baseline: fractions are meaningless
        if self.value < self.crit_frac * self.base:
            return 2
        if self.value < self.warn_frac * self.base:
            return 1
        return 0


class ExplainedVarianceDetector(HysteresisDetector):
    """Value-head explained variance (1 - Var(returns - vpred)/Var(returns)).
    Negative EV means the critic is WORSE than predicting the mean return —
    GAE advantages are then mostly noise. Early training is exempt
    (``warmup``): a fresh value head always starts there."""

    name = "value_ev"

    def __init__(self, warmup: int = 5, warn_ev: float = 0.0, crit_ev: float = -0.5, **kw):
        super().__init__(**kw)
        self.warmup = max(0, int(warmup))
        self.warn_ev = float(warn_ev)
        self.crit_ev = float(crit_ev)
        self.value = 0.0
        self._seen = 0

    def severity(self, ev) -> int:
        self.value = float(ev)
        self._seen += 1
        if self._seen <= self.warmup:
            return 0
        if self.value < self.crit_ev:
            return 2
        if self.value < self.warn_ev:
            return 1
        return 0


def truncation_rate(mask_h, prompt_length: int) -> float:
    """Fraction of rows whose response fills the whole decode budget — no
    EOS inside the window. High sustained truncation means the budget is
    clipping the task (or the policy stopped emitting EOS)."""
    mask = np.asarray(mask_h)
    budget = mask.shape[1] - int(prompt_length)
    if budget <= 0 or mask.shape[0] == 0:
        return 0.0
    lengths = mask[:, prompt_length:].astype(np.int64).sum(axis=1)
    return float(np.mean(lengths >= budget))


def degenerate_rate(tokens_h, mask_h, prompt_length: int, n: int = 3) -> float:
    """Fraction of rows whose response repeats an n-gram — the loop/stutter
    signature of a collapsing sampler. Rows shorter than 2n tokens cannot
    exhibit a repeat and count as clean."""
    tokens = np.asarray(tokens_h)
    mask = np.asarray(mask_h)
    if tokens.shape[0] == 0:
        return 0.0
    hits = 0
    for i in range(tokens.shape[0]):
        row = tokens[i, prompt_length:][mask[i, prompt_length:] > 0]
        if row.size < 2 * n:
            continue
        seen = set()
        for j in range(row.size - n + 1):
            gram = tuple(int(t) for t in row[j : j + n])
            if gram in seen:
                hits += 1
                break
            seen.add(gram)
    return float(hits) / float(tokens.shape[0])


class RolloutSentinel(HysteresisDetector):
    """Host-side degenerate-sample sentinel over each rollout chunk:
    truncation rate and repeated-n-gram rate. Degeneracy drives CRIT;
    a truncation wall alone WARNs (long-answer tasks legitimately live
    near the budget)."""

    name = "rollout"

    def __init__(self, warn_trunc: float = 0.95, warn_degen: float = 0.3,
                 crit_degen: float = 0.7, **kw):
        super().__init__(**kw)
        self.warn_trunc = float(warn_trunc)
        self.warn_degen = float(warn_degen)
        self.crit_degen = float(crit_degen)
        self.trunc = 0.0
        self.degen = 0.0

    def severity(self, obs) -> int:
        self.trunc = float(obs.get("trunc", 0.0))
        self.degen = float(obs.get("degen", 0.0))
        if self.degen >= self.crit_degen:
            return 2
        if self.trunc >= self.warn_trunc or self.degen >= self.warn_degen:
            return 1
        return 0


class MixedVersionDetector(HysteresisDetector):
    """Token-granularity staleness watch for in-flight weight updates: the
    fraction of a consumed batch's response tokens NOT produced by its
    freshest weight version. Some mix is the whole point of pushing weights
    mid-decode (episodes straddle a version switch); a batch that is MOSTLY
    old tokens means pushes outpace decode and the learner is training on
    yesterday's policy — WARN at ``warn_frac``, CRIT at ``crit_frac``.
    Fed by the fleet learner feed (fleet/runner.py) alongside the
    ``fleet/mixed_version_tokens`` gauge."""

    name = "mixed_version"

    def __init__(self, warn_frac: float = 0.5, crit_frac: float = 0.9, **kw):
        super().__init__(**kw)
        self.warn_frac = float(warn_frac)
        self.crit_frac = float(crit_frac)
        self.frac = 0.0

    def severity(self, obs) -> int:
        mixed = float(obs.get("mixed_tokens", 0.0))
        total = float(obs.get("total_tokens", 0.0))
        self.frac = mixed / total if total > 0 else 0.0
        if self.frac >= self.crit_frac:
            return 2
        if self.frac >= self.warn_frac:
            return 1
        return 0


@dataclass
class LineageRecord:
    """Per-chunk provenance: which weights produced these rows, how stale
    they were by the time they train, and how degenerate they looked at the
    host boundary. One JSON line per chunk in ``<ckpt_dir>/lineage.jsonl``.

    ``version_spans`` extends the scalar ``weight_version`` tag to span
    form for in-flight weight updates (PR 17): ``[[version, n_tokens],
    ...]`` aggregated over the chunk's episodes — which versions produced
    HOW MANY of the chunk's tokens, not just which version finished it.
    None on the phase-boundary paths, so pre-span lineage files load
    unchanged (``from_json`` defaults missing fields)."""

    step: int
    weight_version: int
    staleness: float
    rows: int
    truncation_rate: float
    degenerate_rate: float
    mean_score: float
    time: float
    version_spans: list = None

    def to_json(self) -> str:
        return json.dumps(asdict(self))

    @classmethod
    def from_json(cls, line: str) -> "LineageRecord":
        d = json.loads(line)
        return cls(
            **{k: d[k] for k in cls.__dataclass_fields__ if k in d}
        )


class HealthMonitor:
    """Thread-safe front door for the detectors.

    ``observe_train`` runs on the main thread at the trainer's log boundary;
    ``observe_chunk`` runs on whichever thread the orchestrator scores
    rollouts on (the producer thread under the overlapped schedules);
    ``gauges``/``healthz`` are read by the Tracker merge and the live
    exporter. One lock serializes everything — the work per call is a few
    scalar comparisons, nowhere near the dispatch path."""

    def __init__(self, *, warmup: int = 5, warn_streak: int = 2, crit_streak: int = 4,
                 lineage_path=None):
        streaks = dict(warn_streak=warn_streak, crit_streak=crit_streak)
        self.reward = RewardDriftDetector(warmup=warmup, **streaks)
        self.kl = KLHealthDetector(warmup=warmup, **streaks)
        self.entropy = EntropyCollapseDetector(warmup=warmup, **streaks)
        self.value_ev = ExplainedVarianceDetector(warmup=warmup, **streaks)
        self.rollout = RolloutSentinel(**streaks)
        self.detectors = {
            d.name: d
            for d in (self.reward, self.kl, self.entropy, self.value_ev, self.rollout)
        }
        for d in self.detectors.values():
            d.on_crit = self._escalate
        self.lineage_path = lineage_path
        self.lineage = deque(maxlen=256)
        self._staleness_since_hist = []
        self._lock = threading.Lock()
        # Drill latches (TRLX_TPU_FAULTS=reward_drift@N / entropy_collapse@N):
        # perturb the OBSERVED stats only — training never sees them.
        self.reward_offset = 0.0
        self.entropy_scale = 1.0
        self._drift_from_call = None

    def register_detector(self, detector: HysteresisDetector):
        """Adopt an externally-owned detector (graftfleet's
        FleetStragglerDetector): its state rides the health/* gauges and
        /healthz, and a CRIT transition escalates through the same incident
        hook as the built-ins. The OWNER keeps feeding observe() — the
        monitor only reads state."""
        with self._lock:
            self.detectors[detector.name] = detector
            detector.on_crit = self._escalate
        return detector

    # ------------------------------------------------------------ drills

    def inject_reward_drift(self, from_call=None):
        """``from_call`` keys the offset to a reward-call index: with the
        overlapped schedules the drill fires on the score-worker thread while
        EARLIER calls' observations are still in flight on another thread, so
        a bare wall-clock latch would contaminate the warmup baseline and
        suppress the very z-score the drill exists to trip."""
        self.reward_offset = float(
            os.environ.get("TRLX_TPU_REWARD_DRIFT_DELTA", "") or 1e3
        )
        self._drift_from_call = None if from_call is None else int(from_call)

    def _reward_offset_for(self, call) -> float:
        if not self.reward_offset:
            return 0.0
        if self._drift_from_call is None or call is None:
            return self.reward_offset
        return self.reward_offset if int(call) >= self._drift_from_call else 0.0

    def inject_entropy_collapse(self):
        self.entropy_scale = float(
            os.environ.get("TRLX_TPU_ENTROPY_COLLAPSE_SCALE", "") or 0.01
        )

    # ------------------------------------------------------------ escalation

    def _escalate(self, detector, obs):
        """CRIT -> incident bundle, through the same emergency hook the
        collective-timeout abort path uses (anomaly.register_emergency): the
        trainer registered its IncidentCapture there when any observability
        feature armed, and this may run on a producer thread with no trainer
        reference in scope."""
        from trlx_tpu.observability.anomaly import emergency_capture

        detail = {"detector": detector.name, "severity": detector.last_severity}
        if isinstance(obs, dict):
            detail.update({k: v for k, v in obs.items() if isinstance(v, (int, float))})
        else:
            try:
                detail["observation"] = float(obs)
            except (TypeError, ValueError):
                pass
        emergency_capture(f"health_{detector.name}", detail=detail)

    # ------------------------------------------------------------ feeds

    def observe_train(self, stats, step: int, *, kl_coef=None, kl_target=None,
                      kl_init_coef=None):
        """Log-boundary feed: judge the per-step stats the trainer already
        synced to host. Missing keys are skipped (ILQL has no mean_kl)."""
        with self._lock:
            entropy = stats.get("mean_entropy")
            if entropy is not None:
                self.entropy.observe(float(entropy) * self.entropy_scale)
            ev = stats.get("explained_variance")
            if ev is not None:
                self.value_ev.observe(float(ev))
            kl = stats.get("mean_kl")
            if kl is not None or kl_coef is not None:
                self.kl.observe(
                    {"kl": kl, "target": kl_target, "coef": kl_coef,
                     "init_coef": kl_init_coef}
                )

    def observe_chunk(self, tokens_h, mask_h, prompt_length: int, *, scores,
                      weight_version: int, staleness, step: int,
                      reward_call=None, version_spans=None):
        """Rollout-boundary feed, one call per scored chunk: reward drift
        over the chunk's mean score, the degenerate-sample sentinels over
        its token grid, and the chunk's lineage record. ``reward_call`` is
        the chunk's reward-call index (drill offset keying);
        ``version_spans`` is the chunk's per-token weight-version aggregate
        (``[[version, n_tokens], ...]``, engine in-flight updates) — None
        keeps the record byte-compatible with the scalar-tag paths."""
        scores = np.asarray(scores, dtype=np.float64)
        offset = self._reward_offset_for(reward_call)
        mean_score = float(scores.mean()) + offset if scores.size else 0.0
        trunc = truncation_rate(mask_h, prompt_length)
        degen = degenerate_rate(tokens_h, mask_h, prompt_length)
        record = LineageRecord(
            step=int(step),
            weight_version=int(weight_version),
            staleness=float(staleness),
            rows=int(np.asarray(mask_h).shape[0]),
            truncation_rate=trunc,
            degenerate_rate=degen,
            mean_score=mean_score,
            time=time.time(),
            version_spans=(
                [[None if v is None else int(v), int(k)] for v, k in version_spans]
                if version_spans
                else None
            ),
        )
        with self._lock:
            self.reward.observe(mean_score)
            self.rollout.observe({"trunc": trunc, "degen": degen})
            self.lineage.append(record)
            self._staleness_since_hist.append(float(staleness))
            if self.lineage_path:
                try:
                    # Line-atomic single-write append (utils/jsonl contract):
                    # a killed host tears at most the final lineage record.
                    # The spans field is span-form-only: scalar-tag records
                    # stay byte-identical to pre-span lineage files.
                    rec_d = asdict(record)
                    if rec_d.get("version_spans") is None:
                        rec_d.pop("version_spans", None)
                    jsonl.append_record(self.lineage_path, rec_d)
                except OSError:
                    pass  # lineage is an audit trail, never a crash source

    def observe_reward(self, mean_reward: float, step: int = 0):
        """Offline (ILQL) feed: one reward-distribution observation per
        make_experience batch."""
        with self._lock:
            self.reward.observe(float(mean_reward) + self.reward_offset)

    # ------------------------------------------------------------ outputs

    def gauges(self) -> dict:
        """``health/*`` scalars for the Tracker merge and the exporter: each
        detector's state (0/1/2) + the quantity it judges, and the monotonic
        transition counter."""
        with self._lock:
            g = {
                f"health/{name}_state": float(_LEVEL[d.state])
                for name, d in self.detectors.items()
            }
            g["health/state_changes_total"] = float(
                sum(d.state_changes for d in self.detectors.values())
            )
            g["health/reward_drift_z"] = float(self.reward.z)
            g["health/kl_ratio"] = float(self.kl.ratio)
            g["health/kl_coef"] = float(self.kl.coef)
            g["health/entropy"] = float(self.entropy.value)
            g["health/explained_variance"] = float(self.value_ev.value)
            g["health/truncation_rate"] = float(self.rollout.trunc)
            g["health/degenerate_rate"] = float(self.rollout.degen)
            return g

    def status(self) -> str:
        with self._lock:
            worst = max(_LEVEL[d.state] for d in self.detectors.values())
        return {0: "ok", 1: "degraded", 2: "critical"}[worst]

    def healthz(self) -> dict:
        """JSON payload for the live ``/healthz`` endpoint."""
        status = self.status()
        with self._lock:
            detectors = {
                name: {
                    "state": d.state,
                    "last_severity": d.last_severity,
                    "state_changes": d.state_changes,
                    "observations": d.observations,
                }
                for name, d in self.detectors.items()
            }
        return {"status": status, "detectors": detectors}

    def maybe_log_lineage(self, tracker, step: int):
        """Flush a ``health/lineage_staleness`` histogram covering the chunks
        since the previous flush (no-op when no new chunks landed — keeps
        metrics.jsonl free of empty histogram spam)."""
        with self._lock:
            values, self._staleness_since_hist = self._staleness_since_hist, []
        if values:
            tracker.log_histogram("health/lineage_staleness", values, step=step)
