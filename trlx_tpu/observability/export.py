"""Live metrics endpoint: stdlib-HTTP Prometheus ``/metrics`` + ``/healthz``.

A production RLHF run needs scrapeable health signals while it is ALIVE —
the markdown report renders after the fact, and metrics.jsonl is a file on
one host. This exporter is a zero-dependency ``http.server`` daemon thread
on process 0, armed by ``train.metrics_port`` (``TRLX_TPU_METRICS_PORT``
overrides) and off by default:

- ``GET /metrics``  — Prometheus text exposition (version 0.0.4) of the
  freshest log-boundary scalars + ``health/*`` gauges. Keys are sanitized
  (``/`` and ``-`` are illegal in metric names) and prefixed ``trlx_tpu_``;
  keys ending ``_total`` are typed ``counter``, everything else ``gauge``.
- ``GET /healthz`` — the HealthMonitor's JSON status
  (``ok`` / ``degraded`` / ``critical`` + per-detector states).

Besides gauges, :meth:`MetricsExporter.observe` accumulates cumulative
Prometheus histograms (``_bucket{le=...}`` / ``_sum`` / ``_count``) with
optional labels — graftscope feeds per-lane pipeline-gap, engine
refill-latency, and straggler-by-width distributions through it.

Multi-host: the trainer rolls the gauges up over the existing
``allgather_host`` path (``rollup_window_stats``) BEFORE handing them over,
so process 0 serves fleet-level ``/hostmean`` / ``/hostmax`` views, not its
own shard's numbers.

The handler reads a snapshot under a lock and never touches trainer state —
a scrape can never stall a train step.
"""

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from trlx_tpu.utils import sanitize

__all__ = ["sanitize_metric_name", "MetricsExporter"]

# Prometheus metric names must match [a-zA-Z_:][a-zA-Z0-9_:]* — the tracker's
# slash-namespaced keys (health/kl_ratio, time/train_s, obs/train_mfu_pct)
# and dash-bearing keys are all illegal until sanitized.
_ILLEGAL = re.compile(r"[^a-zA-Z0-9_:]")
_VALID = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")


def sanitize_metric_name(key: str) -> str:
    """Map an arbitrary tracker key to a legal Prometheus metric name:
    every illegal character (``/``, ``-``, ``.``, spaces, ...) becomes
    ``_``, and a leading digit gets a ``_`` prefix."""
    name = _ILLEGAL.sub("_", str(key))
    if not name or not _VALID.match(name):
        name = "_" + name
    return name


def _fmt_value(v: float) -> str:
    v = float(v)
    if v != v:
        return "NaN"
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    return repr(v)


class MetricsExporter:
    """Threaded HTTP server publishing the latest gauge snapshot.

    ``port=0`` binds an ephemeral port (tests); the trainer only constructs
    one when the configured port is > 0. ``update()`` replaces nothing —
    it merges, so gauges logged at different cadences (per-step stats,
    per-window phase stats) coexist in one scrape."""

    def __init__(self, port: int = 0, host: str = "0.0.0.0", prefix: str = "trlx_tpu_",
                 port_file=None):
        self.prefix = prefix
        self._lock = sanitize.make_lock("MetricsExporter._lock")
        self._gauges = {}
        # (key, labels-tuple) -> float — labeled gauge series (set_gauge);
        # rendered merged with the flat gauge of the same name.
        self._labeled_gauges = {}
        # (key, labels-tuple) -> {"buckets": (edges...), "counts": [..],
        # "sum": float, "count": int} — cumulative, Prometheus-style.
        self._histograms = {}
        self._health = None
        self._fleet = None  # graftfleet's /healthz block (set_fleet)
        self._step = 0
        exporter = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # noqa: D102 — silence per-request spam
                pass

            def do_GET(self):  # noqa: N802 — http.server API
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    body = exporter.render_metrics().encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif path == "/healthz":
                    body = (json.dumps(exporter.render_healthz()) + "\n").encode()
                    ctype = "application/json"
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.requested_port = int(port)
        try:
            self._server = ThreadingHTTPServer((host, int(port)), Handler)
        except OSError:
            # Port busy (two hosts on one box, a stale run's exporter): bind
            # an ephemeral port instead of crashing the trainer. The actual
            # port is logged, exposed as the obs/metrics_port gauge, and
            # written to port_file — a scraper can always find it.
            self._server = ThreadingHTTPServer((host, 0), Handler)
        # ThreadingHTTPServer daemonizes handler threads but still JOINS
        # them in server_close() (block_on_close) — one wedged scrape
        # connection would hang trainer teardown forever.
        self._server.block_on_close = False
        self.port = int(self._server.server_address[1])
        if self.requested_port and self.port != self.requested_port:
            import sys

            print(
                f"[trlx_tpu.observability] metrics port {self.requested_port} "
                f"busy — serving /metrics on port {self.port} instead "
                "(see the obs/metrics_port gauge / metrics_port file)",
                file=sys.stderr,
                flush=True,
            )
        with self._lock:
            sanitize.race_access(self, "_gauges", write=True)
            self._gauges["obs/metrics_port"] = float(self.port)
        self.port_file = port_file
        if port_file:
            try:
                with open(port_file, "w") as f:
                    f.write(f"{self.port}\n")
            except OSError:
                pass  # advisory breadcrumb only
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="trlx-metrics-exporter",
            daemon=True,
        )
        self._thread.start()

    def update(self, gauges: dict, step=None, health=None):
        """Merge the freshest scalar gauges (and optionally the health
        payload for ``/healthz``). Non-numeric values are dropped here so a
        stray string in a stats dict can never corrupt the exposition."""
        numeric = {
            k: float(v) for k, v in (gauges or {}).items() if isinstance(v, (int, float))
        }
        with self._lock:
            sanitize.race_access(self, "_gauges", write=True)
            self._gauges.update(numeric)
            if step is not None:
                self._step = int(step)
            if health is not None:
                self._health = health

    def set_fleet(self, payload):
        """Attach a fleet block (graftfleet's per-host heartbeat ages /
        desync / straggler verdict, or the disaggregation feed's
        ``disaggregated`` state) to /healthz. Dict payloads MERGE key-wise:
        the two feeds own disjoint top-level keys and must not clobber each
        other's block."""
        with self._lock:
            if isinstance(payload, dict) and isinstance(self._fleet, dict):
                merged = dict(self._fleet)
                merged.update(payload)
                self._fleet = merged
            else:
                self._fleet = payload

    def set_gauge(self, key: str, value, labels: dict = None):
        """Set one LABELED gauge series (``labels`` distinguishes series
        under one metric name, e.g. ``worker="1"`` on the elastic fleet's
        per-worker gauges). Without labels it is exactly ``update({key:
        value})``. A labeled series renders beside the flat same-name gauge
        under one HELP/TYPE block — Prometheus treats the unlabeled sample
        as the fleet aggregate and each labeled one as a member."""
        if not isinstance(value, (int, float)):
            return
        if not labels:
            self.update({key: value})
            return
        label_key = tuple(sorted((str(k), str(v)) for k, v in labels.items()))
        with self._lock:
            self._labeled_gauges[(key, label_key)] = float(value)

    def observe(self, key: str, values, buckets, labels: dict = None):
        """Fold ``values`` into the cumulative histogram ``key`` (creating
        it with ``buckets`` as its ``le`` edges on first sight). ``labels``
        distinguishes series under one metric name (``lane="score"``,
        ``width="64"``) the Prometheus way."""
        label_key = tuple(sorted((labels or {}).items()))
        edges = tuple(float(b) for b in buckets)
        with self._lock:
            hist = self._histograms.get((key, label_key))
            if hist is None or hist["buckets"] != edges:
                hist = self._histograms[(key, label_key)] = {
                    "buckets": edges,
                    "counts": [0] * (len(edges) + 1),  # +Inf bucket last
                    "sum": 0.0,
                    "count": 0,
                }
            for v in values:
                v = float(v)
                if v != v:
                    continue
                idx = len(edges)
                for i, edge in enumerate(edges):
                    if v <= edge:
                        idx = i
                        break
                hist["counts"][idx] += 1
                hist["sum"] += v
                hist["count"] += 1

    @staticmethod
    def _render_labels(label_key, extra=None):
        pairs = list(label_key) + (extra or [])
        if not pairs:
            return ""
        return "{" + ",".join(f'{k}="{v}"' for k, v in pairs) + "}"

    def render_metrics(self) -> str:
        with self._lock:
            sanitize.race_access(self, "_gauges")
            gauges = dict(self._gauges)
            labeled = dict(self._labeled_gauges)
            histograms = {
                k: {
                    "buckets": h["buckets"],
                    "counts": list(h["counts"]),
                    "sum": h["sum"],
                    "count": h["count"],
                }
                for k, h in self._histograms.items()
            }
            step = self._step
        # Sanitized-name collisions (a/b vs a_b) keep the last writer —
        # exposition must never emit a duplicate metric name. A name's flat
        # sample and its labeled series share one HELP/TYPE block (labeled
        # samples are never duplicates: the label set disambiguates).
        by_name = {}
        for key in sorted(gauges):
            by_name[sanitize_metric_name(self.prefix + key)] = (key, gauges[key])
        labeled_by_name = {}
        for (key, label_key), value in sorted(labeled.items()):
            name = sanitize_metric_name(self.prefix + key)
            labeled_by_name.setdefault(name, (key, []))[1].append((label_key, value))
            by_name.setdefault(name, (key, None))
        lines = []
        for name in sorted(by_name):
            key, value = by_name[name]
            kind = "counter" if key.endswith("_total") else "gauge"
            lines.append(f"# HELP {name} trlx_tpu tracker key {key!r}")
            lines.append(f"# TYPE {name} {kind}")
            if value is not None:
                lines.append(f"{name} {_fmt_value(value)}")
            for label_key, lvalue in labeled_by_name.get(name, ("", []))[1]:
                lines.append(
                    f"{name}{self._render_labels(label_key)} {_fmt_value(lvalue)}"
                )
        hist_by_name = {}
        for (key, label_key), hist in sorted(histograms.items()):
            hist_by_name.setdefault(
                sanitize_metric_name(self.prefix + key), (key, [])
            )[1].append((label_key, hist))
        for name in sorted(hist_by_name):
            key, series = hist_by_name[name]
            lines.append(f"# HELP {name} trlx_tpu tracker key {key!r}")
            lines.append(f"# TYPE {name} histogram")
            for label_key, hist in series:
                cumulative = 0
                for edge, n in zip(hist["buckets"], hist["counts"]):
                    cumulative += n
                    labels = self._render_labels(label_key, [("le", _fmt_value(edge))])
                    lines.append(f"{name}_bucket{labels} {cumulative}")
                labels = self._render_labels(label_key, [("le", "+Inf")])
                lines.append(f"{name}_bucket{labels} {hist['count']}")
                labels = self._render_labels(label_key)
                lines.append(f"{name}_sum{labels} {_fmt_value(hist['sum'])}")
                lines.append(f"{name}_count{labels} {hist['count']}")
        name = sanitize_metric_name(self.prefix + "last_step")
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name} {step}")
        return "\n".join(lines) + "\n"

    def render_healthz(self) -> dict:
        with self._lock:
            health = self._health
            fleet = self._fleet
            step = self._step
        payload = {"status": "unknown", "detectors": {}}
        if health:
            payload.update(health)
        if fleet is not None:
            payload["fleet"] = fleet
        payload["step"] = step
        return payload

    def close(self):
        self._server.shutdown()
        self._thread.join(timeout=5)
        if self._thread.is_alive():
            # serve_forever never returned (wedged handler holding the
            # poll loop) — closing the listener socket under it would
            # race; leak the daemon thread and let exit reap it.
            return
        self._server.server_close()
        sanitize.race_forget(self)
