"""Performance report generator: metrics.jsonl + spans.jsonl -> markdown.

    python -m trlx_tpu.observability.report <checkpoint_dir> [-o report.md]
                                            [--trace-out trace.json]

Merges everything the observability layer wrote during a run into one
readable document: per-window phase breakdown, MFU trend from compiled-cost
FLOPs, staleness distribution, kernel-routing table, span-lane accounting
(with the measured producer/train overlap), and the incident index.
``--trace-out`` additionally emits a ``{"traceEvents": [...]}`` wrapper of
spans.jsonl for chrome://tracing (Perfetto loads the raw JSONL directly).

Multi-host: each host appends to the SAME spans.jsonl (line-atomic, lanes
keyed by pid) and rank 0 writes metrics.jsonl, so the report needs no
gather at read time. For LIVE multi-host window stats,
``rollup_window_stats`` aggregates each host's scalar window over the
existing ``allgather_host`` path — the trainer calls it at the window
boundary so metrics.jsonl carries fleet-mean/max gauges, not just rank 0's.
"""

import argparse
import json
import os
import warnings
from collections import defaultdict

import numpy as np

__all__ = ["build_report", "rollup_window_stats", "main"]


# ------------------------------------------------------------------ rollup


def rollup_window_stats(stats: dict, per_host: bool = False) -> dict:
    """Aggregate one window's scalar stats across hosts.

    Returns ``{key/hostmean, key/hostmax}`` for every float-valued key, via
    ``allgather_host`` — so it MUST be called collectively (every host, same
    window boundary). Identity-shaped at process_count()==1: the mean/max of
    one host is itself (tests exercise this path; pods get the real gather).

    ``per_host=True`` (graftfleet armed — must be config-consistent, the
    flag changes nothing about the gather itself) additionally emits every
    host's own value as ``fleet/host{k}/<key>`` plus ``key/hostmin`` /
    ``key/hostspread`` fleet-level views, all from the SAME gathered matrix
    — no extra collective."""
    import jax

    keys = sorted(k for k, v in stats.items() if isinstance(v, (int, float)))
    if not keys:
        return {}
    row = np.asarray([float(stats[k]) for k in keys], dtype=np.float64)
    if jax.process_count() == 1:
        gathered = row[None, :]
    else:
        from trlx_tpu.parallel.mesh import allgather_host

        gathered = np.asarray(allgather_host(row[None, :])).reshape(-1, len(keys))
    out = {}
    for j, key in enumerate(keys):
        out[f"{key}/hostmean"] = float(gathered[:, j].mean())
        out[f"{key}/hostmax"] = float(gathered[:, j].max())
        if per_host:
            out[f"{key}/hostmin"] = float(gathered[:, j].min())
            out[f"{key}/hostspread"] = float(gathered[:, j].max() - gathered[:, j].min())
            for host in range(gathered.shape[0]):
                out[f"fleet/host{host}/{key}"] = float(gathered[host, j])
    return out


# ----------------------------------------------------------------- loading


def _load_jsonl(path):
    from trlx_tpu.utils.jsonl import read_jsonl

    if not os.path.exists(path):
        return []
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")  # torn tails are routine post-kill
        return read_jsonl(path)


def _scalar_records(metrics):
    return [r for r in metrics if "step" in r and "table" not in r and "histogram" not in r]


def _fmt(value, digits=3):
    if value is None:
        return "-"
    if isinstance(value, float):
        return f"{value:.{digits}f}"
    return str(value)


def _trend(values, width: int = 24) -> str:
    """Coarse text sparkline — enough to see an MFU ramp or collapse."""
    if not values:
        return ""
    marks = " .:-=+*#"
    lo, hi = min(values), max(values)
    span = (hi - lo) or 1.0
    if len(values) > width:
        # Mean-pool to `width` buckets.
        idx = np.array_split(np.asarray(values, dtype=np.float64), width)
        values = [float(chunk.mean()) for chunk in idx if chunk.size]
    return "".join(marks[int((v - lo) / span * (len(marks) - 1))] for v in values)


# ----------------------------------------------------------------- spans


def _lane_summary(spans):
    """Per-(pid, tid) lane accounting + cross-lane overlap of X-events."""
    names = {}
    lanes = defaultdict(lambda: {"events": 0, "busy_us": 0, "top": defaultdict(int)})
    for event in spans:
        key = (event.get("pid", 0), event.get("tid", 0))
        if event.get("ph") == "M" and event.get("name") == "thread_name":
            names[key] = event.get("args", {}).get("name", "?")
        elif event.get("ph") == "X":
            lane = lanes[key]
            lane["events"] += 1
            lane["busy_us"] += int(event.get("dur", 0))
            lane["top"][event.get("name", "?")] += int(event.get("dur", 0))
    rows = []
    for key, lane in sorted(lanes.items()):
        top = max(lane["top"].items(), key=lambda kv: kv[1])[0] if lane["top"] else "-"
        rows.append(
            {
                "pid": key[0],
                "tid": key[1],
                "thread": names.get(key, "?"),
                "events": lane["events"],
                "busy_s": lane["busy_us"] / 1e6,
                "top_span": top,
            }
        )
    return rows


def _overlap_seconds(spans, lane_a_substr: str, lane_b_substr: str):
    """Wall seconds where an X-span on a thread named like A overlaps one on
    a thread named like B — the picture-level form of overlap_fraction."""
    names = {}
    for event in spans:
        if event.get("ph") == "M" and event.get("name") == "thread_name":
            names[(event.get("pid", 0), event.get("tid", 0))] = event.get("args", {}).get("name", "")

    def intervals(substr):
        out = []
        for event in spans:
            if event.get("ph") != "X":
                continue
            lane = names.get((event.get("pid", 0), event.get("tid", 0)), "")
            if substr in lane:
                t0 = event.get("ts", 0)
                out.append((t0, t0 + event.get("dur", 0)))
        out.sort()
        return out

    a, b = intervals(lane_a_substr), intervals(lane_b_substr)
    total, i, j = 0, 0, 0
    while i < len(a) and j < len(b):
        lo = max(a[i][0], b[j][0])
        hi = min(a[i][1], b[j][1])
        if lo < hi:
            total += hi - lo
        if a[i][1] <= b[j][1]:
            i += 1
        else:
            j += 1
    return total / 1e6


# ------------------------------------------------------------- graftscope


def _sink_knob(name: str) -> str:
    """Suggested first knob for a named time sink — the triage table of
    RUNBOOK §12 in code form."""
    if "bubble" in name:
        return "raise method.max_staleness / method.score_queue_depth (hide more rollout behind train)"
    if "refill" in name:
        return "raise method.prefill_batch or method.engine_slots (slots starve between episodes)"
    if "score" in name:
        return "parallelize the reward fn / raise method.score_queue_depth"
    if "producer" in name or "rollout" in name or "decode" in name or "engine" in name:
        return "raise method.engine_steps_per_sync / method.chunk_size (amortize decode sync)"
    if "train" in name:
        return "raise train.batch_size or relax remat (device train step dominates)"
    return "profile with spans.jsonl in Perfetto"


def _graftscope_section(checkpoint_dir):
    """Render graftscope.json (if the run was armed) into the ledger table,
    per-program attribution, slot occupancy rows, and the top-3 time sinks
    with a suggested knob each."""
    lines = ["## Device-time attribution (graftscope)", ""]
    path = os.path.join(checkpoint_dir, "graftscope.json")
    if not os.path.exists(path):
        lines.append("No graftscope snapshot (train.graftscope off — set it or TRLX_TPU_GRAFTSCOPE=1).")
        lines.append("")
        return lines
    try:
        with open(path) as f:
            snap = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        lines.append(f"graftscope.json unreadable: {e}")
        lines.append("")
        return lines
    totals = snap.get("totals", {})
    wall = totals.get("wall_s", 0.0) or 0.0
    lines.append("| wall_s | device_busy_s | host_s | bubble_s | bubble_frac | windows | fences dropped |")
    lines.append("|---|---|---|---|---|---|---|")
    lines.append(
        f"| {_fmt(wall, 2)} | {_fmt(totals.get('device_busy_s', 0.0), 2)} "
        f"| {_fmt(totals.get('host_s', 0.0), 2)} | {_fmt(totals.get('bubble_s', 0.0), 2)} "
        f"| {_fmt(snap.get('bubble_fraction', 0.0), 3)} | {len(snap.get('windows', []))} "
        f"| {snap.get('fences_dropped', 0)} |"
    )
    lines.append("")
    programs = snap.get("programs_s", {})
    if programs:
        lines.append("### Per-program device time (top-K, fence-measured)")
        lines.append("")
        lines.append("| program | device_s | share of wall |")
        lines.append("|---|---|---|")
        for name, sec in sorted(programs.items(), key=lambda kv: -kv[1]):
            share = sec / wall if wall else 0.0
            lines.append(f"| {name} | {_fmt(sec, 2)} | {_fmt(100 * share, 1)}% |")
        lines.append("")
    slots = snap.get("slots", [])
    if slots:
        lines.append("### Engine slot occupancy")
        lines.append("")
        lines.append("| slot | busy_s | episodes | last width |")
        lines.append("|---|---|---|---|")
        for row in slots:
            lines.append(
                f"| {row.get('slot')} | {_fmt(row.get('busy_s', 0.0), 2)} "
                f"| {row.get('episodes', 0)} | {row.get('last_width', 0)} |"
            )
        lines.append(
            f"\ncumulative refill wait: {_fmt(snap.get('refill_wait_total_ms', 0.0), 1)} ms"
        )
        lines.append("")
    # Top-3 time sinks: the window's non-overlapped seconds, ranked.
    sinks = [("pipeline bubble", totals.get("bubble_s", 0.0) or 0.0)]
    sinks += [(f"device: {name}", sec) for name, sec in list(programs.items())[:4]]
    for lane, sec in (snap.get("lane_busy_s", {}) or {}).items():
        sinks.append((f"host {lane} lane", sec or 0.0))
    refill_s = (snap.get("refill_wait_total_ms", 0.0) or 0.0) / 1e3
    if refill_s > 0:
        sinks.append(("engine refill wait", refill_s))
    sinks = sorted(sinks, key=lambda kv: -kv[1])[:3]
    lines.append("### Top-3 time sinks")
    lines.append("")
    lines.append("| sink | seconds | suggested knob |")
    lines.append("|---|---|---|")
    for name, sec in sinks:
        lines.append(f"| {name} | {_fmt(sec, 2)} | {_sink_knob(name)} |")
    lines.append("")
    return lines


# ----------------------------------------------------------------- fleet


def _fleet_section(checkpoint_dir):
    """Render graftfleet's federation artifacts: the merged multi-host
    timeline summary (with the stated clock-alignment bound), the
    per-collective skew table naming the worst-arrival host per site, and
    the per-host heartbeat summary."""
    from trlx_tpu.observability import fleet as obs_fleet
    from trlx_tpu.observability.spans import read_fleet_spans
    from trlx_tpu.resilience.distributed import read_heartbeats

    lines = ["## Fleet (graftfleet)", ""]
    merged = read_fleet_spans(checkpoint_dir)
    arrivals = obs_fleet.read_collective_arrivals(checkpoint_dir)
    if merged["clock"] is None and not arrivals:
        lines.append("No fleet artifacts (train.graftfleet off — set it or TRLX_TPU_GRAFTFLEET=1).")
        lines.append("")
        return lines
    clock = merged["clock"] or {}
    offsets = clock.get("offsets_s", [])
    lines.append(
        f"- merged trace: {len(merged['traceEvents'])} events across host lane(s) "
        f"{merged['hosts']} · clock-alignment error ≤ {merged['alignment_error_s'] * 1e3:.3f}ms "
        f"(estimate uncertainty + drift, fleet_clock.jsonl step {clock.get('step', '?')})"
    )
    if offsets:
        lines.append(
            "- clock offsets vs host 0: "
            + " · ".join(f"host{k} {v * 1e3:+.3f}ms" for k, v in enumerate(offsets))
        )
    lines.append("")
    rows = obs_fleet.collective_skew_table(checkpoint_dir)
    if rows:
        lines.append("### Per-collective skew")
        lines.append("")
        lines.append("| site | occurrences | p50_ms | p95_ms | max_ms | worst host | worst share |")
        lines.append("|---|---|---|---|---|---|---|")
        for row in rows:
            worst = "-" if row["worst_host"] is None else f"host {row['worst_host']}"
            lines.append(
                f"| {row['site']} | {row['count']} | {_fmt(row['p50_ms'], 1)} "
                f"| {_fmt(row['p95_ms'], 1)} | {_fmt(row['max_ms'], 1)} "
                f"| {worst} | {_fmt(row['worst_share'], 2)} |"
            )
        lines.append("")
    beats = read_heartbeats(os.path.join(checkpoint_dir, "heartbeats"))
    if beats:
        lines.append("### Per-host heartbeat summary")
        lines.append("")
        lines.append("| host | last step | phase | progress_t | written_t |")
        lines.append("|---|---|---|---|---|")
        for host, rec in sorted(beats.items()):
            lines.append(
                f"| {host} | {rec.get('step')} | {rec.get('phase')} "
                f"| {_fmt(rec.get('progress_t'), 1)} | {_fmt(rec.get('written_t'), 1)} |"
            )
        lines.append("")
    incident = os.path.join(checkpoint_dir, "incidents")
    fleet_bundles = []
    if os.path.isdir(incident):
        for name in sorted(os.listdir(incident)):
            if os.path.exists(os.path.join(incident, name, "fleet_incident.json")):
                fleet_bundles.append(name)
    if fleet_bundles:
        lines.append(
            "- fleet incident bundles: "
            + " · ".join(f"`incidents/{name}/host<k>/`" for name in fleet_bundles)
        )
        lines.append("")
    return lines


def _numerics_section(checkpoint_dir, scalars):
    """Render graftnum's numerics artifacts: the global grad-norm trend,
    the per-subtree grad/update-ratio table, quantization-error gauges per
    kernel class, and the NaN-provenance verdict of every incident bundle
    that carries a numerics.json."""
    from trlx_tpu.observability import numerics as obs_numerics

    lines = ["## Numerics (graftnum)", ""]
    num_keys = sorted({k for r in scalars for k in r if k.startswith("num/")})
    incidents_dir = os.path.join(checkpoint_dir, "incidents")
    numerics_bundles = []
    if os.path.isdir(incidents_dir):
        for name in sorted(os.listdir(incidents_dir)):
            path = os.path.join(incidents_dir, name, obs_numerics.NUMERICS_FILENAME)
            try:
                with open(path) as f:
                    numerics_bundles.append((name, json.load(f)))
            except (OSError, ValueError):
                continue
    if not num_keys and not numerics_bundles:
        lines.append("No numerics records (train.graftnum off — set it or TRLX_TPU_GRAFTNUM=1).")
        lines.append("")
        return lines
    gnorm = [float(r["num/grad_global_norm"]) for r in scalars if "num/grad_global_norm" in r]
    # NaN records are real data here (the guard-tripped step logs a NaN
    # norm) but poison min/max and the sparkline — count them, trend the rest.
    gnorm_bad = sum(1 for v in gnorm if not np.isfinite(v))
    gnorm_ok = [v for v in gnorm if np.isfinite(v)]
    if gnorm:
        line = f"- global grad norm: {len(gnorm)} records"
        if gnorm_ok:
            line += (
                f" · last finite {_fmt(gnorm_ok[-1])} · max {_fmt(max(gnorm_ok))}"
                f" · trend `{_trend(gnorm_ok)}`"
            )
        if gnorm_bad:
            line += f" · {gnorm_bad} NONFINITE record(s)"
        lines.append(line)
        lines.append("")
    subtrees = sorted(
        {k[len("num/grad_norm/"):] for k in num_keys if k.startswith("num/grad_norm/")}
    )
    if subtrees:
        lines.append("| subtree | grad_norm (last) | param_norm (last) | update_ratio (last) | ratio trend |")
        lines.append("|---|---|---|---|---|")
        for sub in subtrees:
            ratios = [
                float(r[f"num/update_ratio/{sub}"])
                for r in scalars
                if f"num/update_ratio/{sub}" in r
                and np.isfinite(float(r[f"num/update_ratio/{sub}"]))
            ]
            last = {
                col: next(
                    (r[f"num/{col}/{sub}"] for r in reversed(scalars) if f"num/{col}/{sub}" in r),
                    None,
                )
                for col in ("grad_norm", "param_norm", "update_ratio")
            }
            lines.append(
                f"| {sub} | {_fmt(last['grad_norm'], 4)} | {_fmt(last['param_norm'], 2)} "
                f"| {_fmt(last['update_ratio'], 6)} | `{_trend(ratios)}` |"
            )
        lines.append("")
    classes = sorted(
        {k[len("num/quant_err_rms/"):] for k in num_keys if k.startswith("num/quant_err_rms/")}
    )
    if classes:
        version = next(
            (r["num/quant_weight_version"] for r in reversed(scalars) if "num/quant_weight_version" in r),
            None,
        )
        lines.append(
            f"### Quantization error (last handoff, weight version {_fmt(version, 0)})"
        )
        lines.append("")
        lines.append("| kernel class | max_abs_err | rms_err | snr_db |")
        lines.append("|---|---|---|---|")
        for cls in classes:
            row = {
                col: next(
                    (r[f"num/{col}/{cls}"] for r in reversed(scalars) if f"num/{col}/{cls}" in r),
                    None,
                )
                for col in ("quant_err_max", "quant_err_rms", "quant_snr_db")
            }
            lines.append(
                f"| {cls} | {_fmt(row['quant_err_max'], 6)} "
                f"| {_fmt(row['quant_err_rms'], 6)} | {_fmt(row['quant_snr_db'], 1)} |"
            )
        lines.append("")
    if numerics_bundles:
        lines.append("### NaN provenance")
        lines.append("")
        for name, payload in numerics_bundles:
            census = payload.get("grad_census", {}) or {}
            bisect = payload.get("forward_bisect", {}) or {}
            leaves = census.get("nonfinite_leaves", []) or []
            first = bisect.get("first_nonfinite")
            verdict = f"first nonfinite at `{first}`" if first else "forward clean"
            if bisect.get("injected"):
                verdict += f" (drill injection: {bisect['injected']})"
            head = " · ".join(leaf.get("path", "?") for leaf in leaves[:3])
            lines.append(
                f"- `incidents/{name}/numerics.json`: "
                f"{census.get('total_nonfinite_leaves', 0)} nonfinite grad leaves"
                + (f" ({head}{' …' if len(leaves) > 3 else ''})" if leaves else "")
                + f" · {verdict}"
            )
        lines.append("")
    return lines


# ----------------------------------------------------------------- report


def build_report(checkpoint_dir: str) -> str:
    checkpoint_dir = os.path.abspath(checkpoint_dir)
    metrics = _load_jsonl(os.path.join(checkpoint_dir, "metrics.jsonl"))
    # Fleet-aware span load: merges spans.host<k>.jsonl lanes (clock-aligned,
    # host-prefixed tids) when graftfleet ran; falls back to the plain
    # spans.jsonl events unchanged otherwise.
    from trlx_tpu.observability.spans import read_fleet_spans

    spans = read_fleet_spans(checkpoint_dir)["traceEvents"]
    scalars = _scalar_records(metrics)
    lines = [f"# Performance report — `{checkpoint_dir}`", ""]

    # --- run summary ------------------------------------------------------
    steps = [r["step"] for r in scalars if isinstance(r.get("step"), (int, float))]
    hosts = sorted({e.get("pid", 0) for e in spans}) if spans else []
    lines += ["## Run summary", ""]
    lines.append(f"- scalar records: {len(scalars)}" + (f" (steps {int(min(steps))}..{int(max(steps))})" if steps else ""))
    lines.append(f"- span events: {len(spans)}" + (f" across host pid(s) {hosts}" if hosts else ""))
    times = [r["t"] for r in scalars if isinstance(r.get("t"), (int, float))]
    if len(times) >= 2:
        lines.append(f"- metrics wall span: {times[-1] - times[0]:.1f}s")
    lines.append("")

    # --- phase breakdown per window --------------------------------------
    windows = [r for r in scalars if "time/window_wall_s" in r]
    lines += ["## Phase breakdown (per window)", ""]
    if windows:
        lines.append("| step | rollout_s | score_s | train_s | wall_s | overlap | tokens/s |")
        lines.append("|---|---|---|---|---|---|---|")
        for r in windows[-12:]:
            lines.append(
                "| {} | {} | {} | {} | {} | {} | {} |".format(
                    _fmt(r.get("step"), 0),
                    _fmt(r.get("time/rollout_s")),
                    _fmt(r.get("time/score_s")),
                    _fmt(r.get("time/train_s")),
                    _fmt(r.get("time/window_wall_s")),
                    _fmt(r.get("time/overlap_fraction"), 2),
                    _fmt(r.get("train_tokens_per_s"), 0),
                )
            )
        if len(windows) > 12:
            lines.append(f"\n(last 12 of {len(windows)} windows)")
    else:
        lines.append("No phase windows recorded (serial single-batch run, or PhaseTimer off).")
    lines.append("")

    # --- MFU trend --------------------------------------------------------
    lines += ["## MFU / FLOP throughput (compiled-cost derived)", ""]
    mfu = [(r.get("step"), r["obs/train_mfu_pct"]) for r in scalars if "obs/train_mfu_pct" in r]
    tfl = [r["obs/train_tflops_per_chip"] for r in scalars if "obs/train_tflops_per_chip" in r]
    if mfu:
        values = [v for _, v in mfu]
        lines.append(
            f"- train MFU: last {_fmt(values[-1], 2)}% · mean {_fmt(float(np.mean(values)), 2)}% "
            f"· max {_fmt(max(values), 2)}% over {len(values)} windows"
        )
        lines.append(f"- trend: `{_trend(values)}`")
    elif tfl:
        lines.append(
            f"- train TFLOP/s per chip: last {_fmt(tfl[-1], 2)} · mean {_fmt(float(np.mean(tfl)), 2)} "
            "(peak FLOP/s unknown — set TRLX_TPU_PEAK_TFLOPS for an MFU %)"
        )
    else:
        lines.append("No compiled-cost gauges recorded (train.device_telemetry off).")
    lines.append("")

    # --- staleness --------------------------------------------------------
    lines += ["## Staleness", ""]
    stale = [r for r in scalars if "staleness/mean" in r]
    hists = [r for r in metrics if r.get("histogram") == "staleness"]
    if stale:
        means = [r["staleness/mean"] for r in stale]
        maxes = [r.get("staleness/max", 0.0) for r in stale]
        lines.append(
            f"- per-batch staleness: mean {_fmt(float(np.mean(means)), 3)} · "
            f"max {_fmt(float(np.max(maxes)), 1)} over {len(stale)} batches"
        )
    if hists:
        last = hists[-1]
        lines.append(
            "- last histogram: " + " · ".join(
                f"{k} {_fmt(last.get(k))}" for k in ("p5", "p50", "p95", "max") if k in last
            )
        )
    if not stale and not hists:
        lines.append("No staleness records (serial on-policy run).")
    lines.append("")

    # --- kernel routing ---------------------------------------------------
    lines += ["## Kernel routing", ""]
    routed = [r for r in scalars if "obs/fused_logprob_active" in r]
    if routed:
        last = routed[-1]
        lines.append("| gauge | value |")
        lines.append("|---|---|")
        for key in sorted(k for k in last if k.startswith("obs/") and ("active" in k or "fallback" in k)):
            lines.append(f"| {key} | {_fmt(last[key], 0)} |")
        fallbacks = [k for k in last if k.endswith("_fallback") and last[k]]
        if fallbacks:
            lines.append("")
            lines.append(f"**WARNING: silent kernel fallback active: {fallbacks}** — see RUNBOOK.md §8.")
    else:
        lines.append("No routing gauges recorded.")
    programs_path = os.path.join(checkpoint_dir, "programs.json")
    if os.path.exists(programs_path):
        try:
            with open(programs_path) as f:
                programs = json.load(f)
        except (OSError, ValueError):
            programs = {}
        if programs:
            lines += ["", "### Monitored programs", "", "| program | phase | dispatches | GFLOPs | temp MiB |", "|---|---|---|---|---|"]
            for name, prog in sorted(programs.items()):
                variants = prog.get("variants", [])
                flops = max((v.get("flops") or 0.0 for v in variants), default=0.0)
                temp = max((v.get("temp_size_in_bytes") or 0 for v in variants), default=0)
                lines.append(
                    f"| {name} | {prog.get('phase')} | {prog.get('dispatches')} "
                    f"| {_fmt(flops / 1e9, 2)} | {_fmt(temp / 2**20, 1)} |"
                )
    lines.append("")

    # --- span lanes -------------------------------------------------------
    lines += ["## Span lanes", ""]
    if spans:
        lanes = _lane_summary(spans)
        lines.append("| pid | thread | events | busy_s | top span |")
        lines.append("|---|---|---|---|---|")
        for lane in lanes:
            lines.append(
                f"| {lane['pid']} | {lane['thread']} | {lane['events']} "
                f"| {_fmt(lane['busy_s'], 2)} | {lane['top_span']} |"
            )
        overlap = _overlap_seconds(spans, "trlx-rollout-producer", "MainThread")
        if overlap > 0:
            lines.append("")
            lines.append(f"- producer/train overlap: {_fmt(overlap, 2)}s of wall where both lanes were busy")
        lines.append("")
        lines.append("Load the raw lanes in Perfetto (https://ui.perfetto.dev): open `spans.jsonl` directly,")
        lines.append("or `--trace-out trace.json` for chrome://tracing.")
    else:
        lines.append("No spans recorded (train.trace_spans off — set it or TRLX_TPU_SPANS=1).")
    lines.append("")

    # --- graftscope: device-time attribution & time sinks -----------------
    lines += _graftscope_section(checkpoint_dir)

    # --- graftfleet: cross-host federation --------------------------------
    lines += _fleet_section(checkpoint_dir)

    # --- graftnum: numerics observatory -----------------------------------
    lines += _numerics_section(checkpoint_dir, scalars)

    # --- training health --------------------------------------------------
    incidents_dir = os.path.join(checkpoint_dir, "incidents")
    bundles = sorted(os.listdir(incidents_dir)) if os.path.isdir(incidents_dir) else []
    lines += ["## Training health", ""]
    state_names = {0: "OK", 1: "WARN", 2: "CRIT"}
    state_keys = sorted(
        {k for r in scalars for k in r if k.startswith("health/") and k.endswith("_state")}
    )
    if state_keys:
        lines.append("| detector | last | worst | records | trend (0=OK 1=WARN 2=CRIT) |")
        lines.append("|---|---|---|---|---|")
        for key in state_keys:
            series = [float(r[key]) for r in scalars if key in r]
            detector = key[len("health/") : -len("_state")]
            lines.append(
                "| {} | {} | {} | {} | `{}` |".format(
                    detector,
                    state_names.get(int(series[-1]), "?"),
                    state_names.get(int(max(series)), "?"),
                    len(series),
                    _trend(series),
                )
            )
        changes = [r["health/state_changes_total"] for r in scalars if "health/state_changes_total" in r]
        if changes:
            lines.append("")
            lines.append(f"- state transitions: {int(changes[-1])} total")
        # Cross-links: incident bundles this monitor escalated (reason
        # health_<detector>) — the full bundle table is in ## Incidents.
        health_bundles = []
        for name in bundles:
            try:
                with open(os.path.join(incidents_dir, name, "incident.json")) as f:
                    manifest = json.load(f)
            except (OSError, ValueError):
                continue
            if str(manifest.get("reason", "")).startswith("health_"):
                health_bundles.append((name, manifest.get("reason")))
        if health_bundles:
            lines.append(
                "- escalated incidents: "
                + " · ".join(f"{reason} -> `incidents/{name}/`" for name, reason in health_bundles)
            )
        hists = [r for r in metrics if r.get("histogram") == "health/lineage_staleness"]
        if hists:
            last = hists[-1]
            lines.append(
                "- lineage staleness (last window): " + " · ".join(
                    f"{k} {_fmt(last.get(k))}"
                    for k in ("count", "p5", "p50", "p95", "max")
                    if k in last
                )
            )
        lineage_path = os.path.join(checkpoint_dir, "lineage.jsonl")
        if os.path.exists(lineage_path):
            records = _load_jsonl(lineage_path)
            if records:
                stale_vals = [r.get("staleness", 0.0) for r in records]
                lines.append(
                    f"- lineage records: {len(records)} chunks · staleness mean "
                    f"{_fmt(float(np.mean(stale_vals)))} max {_fmt(float(np.max(stale_vals)), 1)} "
                    "(`lineage.jsonl`)"
                )
    else:
        lines.append("No health records (train.health_monitor off — set it or TRLX_TPU_HEALTH=1).")
    lines.append("")

    # --- incidents --------------------------------------------------------
    lines += ["## Incidents", ""]
    if bundles:
        lines.append("| step | reason | sections | bundle |")
        lines.append("|---|---|---|---|")
        for name in bundles:
            manifest_path = os.path.join(incidents_dir, name, "incident.json")
            reason, sections = "?", "?"
            try:
                with open(manifest_path) as f:
                    manifest = json.load(f)
                reason = manifest.get("reason", "?")
                sections = ",".join(k for k, v in manifest.get("sections", {}).items() if v == "ok")
            except (OSError, ValueError):
                pass
            lines.append(f"| {name} | {reason} | {sections} | `incidents/{name}/` |")
    else:
        lines.append("None.")
    lines.append("")
    return "\n".join(lines)


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m trlx_tpu.observability.report",
        description="Render a markdown performance report from a run's checkpoint dir.",
    )
    parser.add_argument("checkpoint_dir", help="directory holding metrics.jsonl / spans.jsonl")
    parser.add_argument("-o", "--out", default=None, help="write the report here (default: stdout)")
    parser.add_argument(
        "--trace-out",
        default=None,
        help="also write spans.jsonl as a {'traceEvents': [...]} JSON for chrome://tracing",
    )
    args = parser.parse_args(argv)

    report = build_report(args.checkpoint_dir)
    if args.out:
        with open(args.out, "w") as f:
            f.write(report)
        print(f"wrote {args.out}")
    else:
        print(report)

    if args.trace_out:
        # Fleet-aware: merges spans.host<k>.jsonl into clock-aligned per-host
        # lanes when graftfleet ran; identical to the plain spans.jsonl dump
        # otherwise.
        from trlx_tpu.observability.spans import read_fleet_spans

        spans = read_fleet_spans(os.path.abspath(args.checkpoint_dir))["traceEvents"]
        with open(args.trace_out, "w") as f:
            json.dump({"traceEvents": spans}, f)
        print(f"wrote {args.trace_out} ({len(spans)} events)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
