"""trlx_tpu — a TPU-native (JAX/XLA/pjit/Pallas) RLHF framework.

Capability-equivalent to trlx v0.2.0 (reference: /root/reference), redesigned
TPU-first: functional Flax models over a `jax.sharding.Mesh`, single pjit'd
train steps, `lax.scan`/`lax.while_loop` control flow, Pallas kernels for hot
ops, and XLA collectives (psum/all_gather/ppermute) over ICI/DCN instead of
NCCL/DeepSpeed.

Public API mirrors the reference's single entry point
(reference: trlx/__init__.py:1, trlx/trlx.py:13-93):

    import trlx_tpu
    trlx_tpu.train("gpt2", reward_fn=...)          # online PPO
    trlx_tpu.train("gpt2", dataset=(samples, rs))  # offline ILQL

The ``train`` export is lazy (PEP 562): bare ``import trlx_tpu`` must stay
jax-free so jax-less subsystems (``python -m trlx_tpu.analysis``, the
CPU-only `make lint` CI job) can import the package without the accelerator
stack.
"""

__version__ = "0.1.0"

__all__ = ["train", "__version__"]


def __getattr__(name):
    if name == "train":
        from trlx_tpu.trlx import train

        return train
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
