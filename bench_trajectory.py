"""Bench-trajectory regression gate: fold the per-run bench artifacts into
one tracked series and fail when the flagship numbers move backwards.

    python bench_trajectory.py [--check/--no-check] [--tolerance 0.10]
                               [--out BENCH_TRAJECTORY.json]

Inputs (all already tracked in the repo root):

- ``BENCH_r0*.json`` — one file per bench run (the ``parsed`` block carries
  ``value`` in samples/s/chip and, from r02 on, ``train_mfu_pct``). Runs
  whose parse failed but whose ``tail`` still contains the bench's JSON
  metric line are recovered from the tail; runs with no data at all are
  recorded as gaps, not silently dropped.
- ``BENCH_SMOKE.json`` — the CPU smoke's informational throughputs
  (rollout/fused-loss tokens/s, overlap fraction). Folded into the series
  for trend reading; throughputs are never gated (CPU smoke numbers
  measure the harness, not the hardware). The one exception is the paged
  KV record's CONTRACT fields — slot-capacity ratio and prefix prefill
  savings are hardware-independent invariants, so the gate fails when
  they fall below the 1.5x / >0 floors.
- ``BENCH_MANIFEST.jsonl`` / ``BENCH_MANIFEST_rNN.jsonl`` — bench.py's
  crash-proof RunManifest journal (observability/graftscope). For runs
  whose artifact carries no data, the manifest's forensic reason (which
  phase/candidate the run was killed in, the last child failure's rc and
  stderr tail) replaces the generic ``no_data`` reason.

Output: ``BENCH_TRAJECTORY.json`` — the full series plus the gate verdict.

The gate compares the LATEST run carrying data against the BEST prior run
with the SAME ``metric`` string (bench configs changed across early runs —
r01 benched a small arch; comparing across configs would be noise): exit 1
when samples/s/chip or train MFU regresses more than ``--tolerance``
(default 10%). Wired as a non-blocking CI job (.github/workflows/tests.yml)
so the trajectory informs without gating merges. Stdlib-only on purpose —
the CI job needs no installs.
"""

import argparse
import glob
import json
import os
import re
import sys

RUN_GLOB = "BENCH_r[0-9]*.json"
SMOKE_PATH = "BENCH_SMOKE.json"
MANIFEST_PATH = "BENCH_MANIFEST.jsonl"


def _read_manifest(path: str):
    """Inline stdlib mirror of observability/graftscope.RunManifest.read —
    this script must stay import-light (the CI job installs nothing), so it
    cannot import the observability package. tests/test_observability.py
    asserts the two produce the same summary, so they cannot drift.

    Folds a possibly-torn, possibly end-less line-atomic manifest into
    ``{"valid", "complete", "rc", "reason", "last_heartbeat", "partial"}``.
    """
    try:
        with open(path, "rb") as f:
            lines = f.read().split(b"\n")
    except OSError:
        return None
    records = []
    for line in lines:
        if not line.strip():
            continue
        try:
            records.append(json.loads(line))
        except ValueError:
            continue  # torn tail (SIGKILL mid-append) — every prior line counts
    begin = next((r for r in records if r.get("event") == "begin"), None)
    if begin is None:
        return None
    end = next((r for r in reversed(records) if r.get("event") == "end"), None)
    heartbeats = [r for r in records if r.get("event") == "heartbeat"]
    children = [r for r in records if r.get("event") == "child"]
    partial = next(
        (r.get("metrics") for r in reversed(records) if r.get("event") == "partial"),
        None,
    )
    if end is not None:
        reason = end.get("reason") or f"completed rc={end.get('rc')}"
        rc = end.get("rc")
    else:
        rc = None
        if heartbeats:
            last = heartbeats[-1]
            where = last.get("phase", "?")
            cand = last.get("candidate")
            reason = f"run killed mid-flight during {where}" + (
                f" (candidate {cand})" if cand else ""
            )
        else:
            reason = "run killed before first heartbeat"
        failed = [c for c in children if c.get("rc") not in (0, None)]
        if failed:
            tail = (failed[-1].get("stderr_tail") or "").strip().splitlines()
            last_line = tail[-1][:160] if tail else ""
            reason += (
                f"; last child failure {failed[-1].get('label')} "
                f"rc={failed[-1].get('rc')}"
            ) + (f": {last_line}" if last_line else "")
    return {
        "valid": True,
        "complete": end is not None,
        "rc": rc,
        "reason": reason,
        "last_heartbeat": heartbeats[-1] if heartbeats else None,
        "partial": partial,
    }


def _attach_manifest_reasons(runs, manifest_path=MANIFEST_PATH):
    """For no-data runs, surface the RunManifest's forensic reason instead
    of the generic artifact-side one. A per-run ``BENCH_MANIFEST_rNN.jsonl``
    beside the artifact wins; the shared ``BENCH_MANIFEST.jsonl`` (bench.py
    truncates it per run, so it describes ONE run) applies only to the
    latest artifact — attributing it to an older gap would be a lie."""
    for i, entry in enumerate(runs):
        if not entry.get("no_data") and "error" not in entry:
            continue
        summary = None
        if entry.get("run") is not None:
            per_run = os.path.join(
                os.path.dirname(entry["source"]) or ".",
                f"BENCH_MANIFEST_r{entry['run']:02d}.jsonl",
            )
            summary = _read_manifest(per_run)
        if summary is None and i == len(runs) - 1:
            summary = _read_manifest(manifest_path)
        if summary is None or (summary["complete"] and summary.get("rc") == 0):
            # A clean-finish manifest can't explain a no-data artifact —
            # keep the artifact-side reason.
            continue
        entry["reason"] = summary["reason"]
        entry["manifest"] = True
        if summary.get("partial"):
            entry["manifest_partial"] = summary["partial"]


def _parse_run(path: str):
    """One trajectory entry per bench-run artifact. ``parsed`` when the
    harness extracted the metric line; otherwise scrape the tail for the
    bench's own JSON line; otherwise a data-less gap entry."""
    try:
        with open(path) as f:
            run = json.load(f)
    except (OSError, ValueError) as e:
        return {"source": path, "error": f"{type(e).__name__}: {e}"}
    # basename only: a directory component like /tmp/xyr42/ must not win
    m = re.search(r"r(\d+)", os.path.basename(path))
    entry = {"source": path, "run": int(m.group(1)) if m else None, "rc": run.get("rc")}
    parsed = run.get("parsed")
    tail_error = None
    if not parsed:
        for line in reversed(run.get("tail", "").splitlines()):
            line = line.strip()
            if line.startswith("{") and '"metric"' in line:
                try:
                    parsed = json.loads(line)
                    break
                except ValueError as e:
                    tail_error = f"{type(e).__name__}: {e}"
                    continue
    if not parsed or not isinstance(parsed.get("value"), (int, float)):
        entry["no_data"] = True
        # Say WHY the run carries no data, so a gap in the trajectory is
        # triageable from BENCH_TRAJECTORY.json alone: a failed run, a parsed
        # block missing its numeric value, a metric line that would not
        # parse, or no metric line at all. The gate below is unchanged —
        # no_data entries were never gated.
        rc = run.get("rc")
        if rc not in (0, None):
            entry["reason"] = f"bench run exited rc={rc}"
        elif parsed:
            entry["reason"] = "parsed metric block has no numeric 'value'"
        elif tail_error:
            entry["reason"] = f"metric line in tail failed to parse: {tail_error}"
        else:
            entry["reason"] = "no parseable metric line in artifact tail"
        return entry
    entry["metric"] = parsed.get("metric")
    entry["samples_per_sec_per_chip"] = float(parsed["value"])
    if isinstance(parsed.get("train_mfu_pct"), (int, float)):
        entry["train_mfu_pct"] = float(parsed["train_mfu_pct"])
    return entry


def _parse_smoke(path: str):
    try:
        with open(path) as f:
            smoke = json.load(f)
    except (OSError, ValueError):
        return None
    out = {"source": path}
    rollout = smoke.get("rollout", {})
    fused = smoke.get("fused_loss", {})
    overlap = smoke.get("overlap", {})
    if isinstance(rollout.get("tokens_per_s"), (int, float)):
        out["rollout_tokens_per_s"] = float(rollout["tokens_per_s"])
    if isinstance(fused.get("tokens_per_s"), (int, float)):
        out["fused_loss_tokens_per_s"] = float(fused["tokens_per_s"])
    if isinstance(overlap.get("overlap_fraction_max"), (int, float)):
        out["overlap_fraction_max"] = float(overlap["overlap_fraction_max"])
    engine = smoke.get("decode_engine", {})
    if isinstance(engine.get("decode_tokens_per_s"), (int, float)):
        out["engine_decode_tokens_per_s"] = float(engine["decode_tokens_per_s"])
        if isinstance(engine.get("static_decode_tokens_per_s"), (int, float)):
            out["static_decode_tokens_per_s"] = float(engine["static_decode_tokens_per_s"])
        if isinstance(engine.get("slot_occupancy"), (int, float)):
            out["engine_slot_occupancy"] = float(engine["slot_occupancy"])
    spec = smoke.get("spec_decode", {})
    if isinstance(spec.get("decode_tokens_per_s"), (int, float)):
        out["spec_decode_tokens_per_s"] = float(spec["decode_tokens_per_s"])
        if isinstance(spec.get("accept_rate"), (int, float)):
            out["spec_accept_rate"] = float(spec["accept_rate"])
        if isinstance(spec.get("speedup_vs_nonspec"), (int, float)):
            out["spec_speedup_vs_nonspec"] = float(spec["speedup_vs_nonspec"])
    paged = smoke.get("paged_kv", {})
    if isinstance(paged.get("slot_capacity_ratio"), (int, float)):
        out["paged_slot_capacity_ratio"] = float(paged["slot_capacity_ratio"])
        if isinstance(paged.get("prefill_token_reduction"), (int, float)):
            out["paged_prefill_token_reduction"] = float(paged["prefill_token_reduction"])
        if isinstance(paged.get("prefix_hits_total"), (int, float)):
            out["paged_prefix_hits_total"] = float(paged["prefix_hits_total"])
    fleet = smoke.get("fleet_elastic", {})
    if isinstance(fleet.get("episodes_per_s_2workers"), (int, float)):
        out["fleet_episodes_per_s_2workers"] = float(fleet["episodes_per_s_2workers"])
        if isinstance(fleet.get("episodes_per_s_1worker"), (int, float)):
            out["fleet_episodes_per_s_1worker"] = float(fleet["episodes_per_s_1worker"])
        if isinstance(fleet.get("speedup"), (int, float)):
            out["fleet_elastic_speedup"] = float(fleet["speedup"])
    return out


def build_trajectory(
    run_paths, smoke_path=SMOKE_PATH, tolerance: float = 0.10,
    manifest_path=MANIFEST_PATH,
):
    runs = [_parse_run(p) for p in sorted(run_paths)]
    _attach_manifest_reasons(runs, manifest_path=manifest_path)
    with_data = [r for r in runs if "samples_per_sec_per_chip" in r]
    trajectory = {
        "runs": runs,
        "smoke": _parse_smoke(smoke_path),
        "tolerance": tolerance,
        "regressed": False,
        "verdict": [],
    }
    # Paged-KV gate (the one smoke-sourced gate): the capacity ratio and
    # prefix savings are CONTRACTS, not throughputs — a smoke artifact that
    # stops carrying >= 1.5x slots in the same bytes, or stops saving
    # prefill on template hits, means the paged path regressed regardless
    # of what hardware produced the file.
    smoke = trajectory["smoke"] or {}
    if "paged_slot_capacity_ratio" in smoke:
        ratio = smoke["paged_slot_capacity_ratio"]
        saving = smoke.get("paged_prefill_token_reduction", 0.0)
        if ratio < 1.5 or saving <= 0.0:
            trajectory["regressed"] = True
            trajectory["verdict"].append(
                f"REGRESSION: paged KV smoke carries slot capacity {ratio:.2f}x "
                f"(floor 1.5x) with prefill-token reduction {saving:.3f} — the "
                "paged pool no longer buys slots/prefill in the same cache bytes"
            )
        else:
            trajectory["verdict"].append(
                f"paged KV: {ratio:.2f}x slots in the same cache bytes, "
                f"{saving:.0%} prefill tokens saved by prefix hits — ok"
            )
    if not with_data:
        trajectory["verdict"].append("no bench runs carry data — nothing to gate")
        return trajectory
    latest = with_data[-1]
    trajectory["latest"] = latest
    priors = [r for r in with_data[:-1] if r.get("metric") == latest.get("metric")]
    if not priors:
        trajectory["verdict"].append(
            f"latest run {latest['source']} has no prior run with the same "
            "metric config — trajectory seeded, nothing to gate"
        )
        return trajectory
    best = max(priors, key=lambda r: r["samples_per_sec_per_chip"])
    trajectory["best_prior"] = best
    floor = (1.0 - tolerance) * best["samples_per_sec_per_chip"]
    if latest["samples_per_sec_per_chip"] < floor:
        trajectory["regressed"] = True
        trajectory["verdict"].append(
            f"REGRESSION: samples/s/chip {latest['samples_per_sec_per_chip']:.3f} "
            f"({latest['source']}) is more than {tolerance:.0%} below the best prior "
            f"{best['samples_per_sec_per_chip']:.3f} ({best['source']})"
        )
    else:
        trajectory["verdict"].append(
            f"samples/s/chip {latest['samples_per_sec_per_chip']:.3f} vs best prior "
            f"{best['samples_per_sec_per_chip']:.3f} — within tolerance"
        )
    mfu_priors = [r for r in priors if "train_mfu_pct" in r]
    if "train_mfu_pct" in latest and mfu_priors:
        best_mfu = max(r["train_mfu_pct"] for r in mfu_priors)
        if latest["train_mfu_pct"] < (1.0 - tolerance) * best_mfu:
            trajectory["regressed"] = True
            trajectory["verdict"].append(
                f"REGRESSION: train MFU {latest['train_mfu_pct']:.2f}% is more than "
                f"{tolerance:.0%} below the best prior {best_mfu:.2f}%"
            )
        else:
            trajectory["verdict"].append(
                f"train MFU {latest['train_mfu_pct']:.2f}% vs best prior "
                f"{best_mfu:.2f}% — within tolerance"
            )
    return trajectory


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--out", default="BENCH_TRAJECTORY.json")
    parser.add_argument("--tolerance", type=float, default=0.10)
    parser.add_argument(
        "--no-check",
        action="store_true",
        help="fold the trajectory but always exit 0 (local refresh)",
    )
    args = parser.parse_args(argv)

    trajectory = build_trajectory(
        glob.glob(RUN_GLOB), smoke_path=SMOKE_PATH, tolerance=args.tolerance
    )
    with open(args.out, "w") as f:
        json.dump(trajectory, f, indent=1)
        f.write("\n")
    for line in trajectory["verdict"]:
        print(line)
    print(f"wrote {args.out} ({len(trajectory['runs'])} runs)")
    if trajectory["regressed"] and not args.no_check:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
